"""Python side of the C inference API (native/capi.cc).

The reference exposes C serving via ``paddle/capi`` wrapping its C++
core (``capi/gradient_machine.h:27-73``, ``capi/main.h:27``); here the
engine IS the XLA executor, so the C ABI wraps it through this bridge:
capi.cc embeds (or joins) a CPython interpreter and calls these three
functions. Handles are ints so no Python object crosses the ABI.

Thread-safety: the C side serializes entry through the GIL; each model
handle owns its Executor (compiled-step cache) and Scope, so concurrent
requests against different models never share mutable state, and
against the same model share only the jitted function (thread-safe).
"""

import threading

import numpy as np

_models = {}
_next_id = [1]
_lock = threading.Lock()

_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}


def load_model(dirname, batch_buckets=None, deadline_ms=None):
    """Load an inference dir (JSON __model__ + params) -> int handle.
    With ``batch_buckets`` the handle serves through a bucketed
    ServingEngine (padded shapes against the compile cache, AOT-warmed)
    instead of a raw Executor — the C serving path then shares the
    Python serving layer's shape discipline, metrics, AND resilience:
    replica breakers/failover arm off the ``serving_breaker_*`` flags,
    and ``deadline_ms`` (default: the ``serving_deadline_ms`` flag; 0 =
    none) bounds every forward — an expired call raises
    ServingDeadlineError before occupying a device."""
    from . import config as _config
    from . import io as _io
    from .core.executor import Executor
    from .core.scope import Scope, scope_guard

    if deadline_ms and not batch_buckets:  # 0/None = no deadline
        raise ValueError(
            "deadline_ms needs the bucketed serving path — pass "
            "batch_buckets too (the raw-Executor path has no deadline "
            "enforcement)")
    if batch_buckets:
        from .serving.engine import ServingEngine
        eng = ServingEngine(dirname, buckets=batch_buckets)
        if deadline_ms is None:
            flag_ms = _config.get_flag("serving_deadline_ms")
            deadline_ms = flag_ms if flag_ms else None
        entry = {"serving": eng, "feed_names": list(eng.feed_names),
                 "fetch_names": list(eng.fetch_names),
                 "deadline_ms": deadline_ms,
                 "lock": threading.Lock()}
    else:
        scope = Scope()
        exe = Executor()
        with scope_guard(scope):
            program, feed_names, fetch_names = _io.load_inference_model(
                dirname, exe, scope=scope)
        entry = {"exe": exe, "scope": scope, "program": program,
                 "feed_names": feed_names, "fetch_names": fetch_names,
                 "lock": threading.Lock()}
    with _lock:
        handle = _next_id[0]
        _next_id[0] += 1
        _models[handle] = entry
    return handle


def forward(handle, inputs):
    """inputs: [(name, bytes_or_buffer, shape tuple, dtype code)].
    Returns [(name, float32 C-contiguous array)] for each fetch."""
    entry = _models[handle]
    feed = {}
    for name, buf, shape, dtype_code in inputs:
        dt = _DTYPES[int(dtype_code)]
        arr = np.frombuffer(buf, dtype=dt).reshape(
            [int(s) for s in shape])
        feed[name] = arr
    if "serving" in entry:
        # engine is itself thread-safe; deadlines/breakers apply here
        outs = entry["serving"].run(feed,
                                    deadline_ms=entry["deadline_ms"])
    else:
        with entry["lock"]:
            outs = entry["exe"].run(entry["program"], feed=feed,
                                    fetch_list=entry["fetch_names"],
                                    scope=entry["scope"])
    result = []
    for name, val in zip(entry["fetch_names"], outs):
        a = np.ascontiguousarray(np.asarray(val), dtype=np.float32)
        result.append((name, a, list(a.shape)))
    return result


def release(handle):
    with _lock:
        entry = _models.pop(handle, None)
    if entry and "serving" in entry:
        entry["serving"].close()  # stop the breaker probe thread


def feed_fetch_names(handle):
    e = _models[handle]
    return list(e["feed_names"]), list(e["fetch_names"])
