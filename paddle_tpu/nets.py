"""Composite networks (reference ``python/paddle/v2/fluid/nets.py``:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, pool_type="max",
                         param_attr=None, **kwargs):
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act, **kwargs)
    return layers.pool2d(conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride,
                         **kwargs)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_stride=1,
                   pool_type="max", **kwargs):
    tmp = input
    if isinstance(conv_with_batchnorm, bool):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if isinstance(conv_batchnorm_drop_rate, (int, float)):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * \
            len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not conv_with_batchnorm[i] else None
        tmp = layers.conv2d(tmp, num_filters=nf,
                            filter_size=conv_filter_size,
                            padding=conv_padding, act=local_act, **kwargs)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act, **kwargs)
            if conv_batchnorm_drop_rate[i] > 0:
                tmp = layers.dropout(tmp,
                                     dropout_prob=conv_batchnorm_drop_rate[i],
                                     **kwargs)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, **kwargs)


def sequence_conv_pool(input, num_filters, filter_size, length=None,
                       act="sigmoid", pool_type="max", **kwargs):
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size, act=act,
                                    **kwargs)
    return layers.sequence_pool(conv_out, pool_type=pool_type,
                                length=length, **kwargs)


def glu(input, dim=-1, **kwargs):
    a, b = layers.split(input, num_or_sections=2, dim=dim, **kwargs)
    gate = layers.sigmoid(b, **kwargs)
    return layers.elementwise_mul(a, gate, **kwargs)


def scaled_dot_product_attention(queries, keys, values, **kwargs):
    ctx, attn = layers.dot_product_attention(queries, keys, values,
                                             **kwargs)
    return ctx
