"""Composite networks (reference ``python/paddle/v2/fluid/nets.py``:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention", "simple_lstm",
           "simple_gru", "bidirectional_lstm", "bidirectional_gru",
           "simple_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, pool_type="max",
                         param_attr=None, **kwargs):
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act, **kwargs)
    return layers.pool2d(conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride,
                         **kwargs)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_stride=1,
                   pool_type="max", **kwargs):
    tmp = input
    if isinstance(conv_with_batchnorm, bool):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if isinstance(conv_batchnorm_drop_rate, (int, float)):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * \
            len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not conv_with_batchnorm[i] else None
        tmp = layers.conv2d(tmp, num_filters=nf,
                            filter_size=conv_filter_size,
                            padding=conv_padding, act=local_act, **kwargs)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act, **kwargs)
            if conv_batchnorm_drop_rate[i] > 0:
                tmp = layers.dropout(tmp,
                                     dropout_prob=conv_batchnorm_drop_rate[i],
                                     **kwargs)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, **kwargs)


def sequence_conv_pool(input, num_filters, filter_size, length=None,
                       act="sigmoid", pool_type="max", **kwargs):
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size, act=act,
                                    **kwargs)
    return layers.sequence_pool(conv_out, pool_type=pool_type,
                                length=length, **kwargs)


def glu(input, dim=-1, **kwargs):
    a, b = layers.split(input, num_or_sections=2, dim=dim, **kwargs)
    gate = layers.sigmoid(b, **kwargs)
    return layers.elementwise_mul(a, gate, **kwargs)


def scaled_dot_product_attention(queries, keys, values, **kwargs):
    ctx, attn = layers.dot_product_attention(queries, keys, values,
                                             **kwargs)
    return ctx


# -- v2 networks.py composites ----------------------------------------------
# (reference python/paddle/trainer_config_helpers/networks.py:1-1813:
# simple_lstm, simple_gru, bidirectional_lstm/gru, simple_attention)

def simple_lstm(input, size, length=None, is_reverse=False,
                mixed_param_attr=None, lstm_param_attr=None,
                lstm_bias_attr=None, **kwargs):
    """fc gate projection + LSTM over time (reference networks.py
    simple_lstm: mixed full-matrix projection into lstmemory)."""
    proj = layers.fc(input, 4 * size, num_flatten_dims=2,
                     param_attr=mixed_param_attr, bias_attr=False,
                     **kwargs)
    hidden, cell = layers.dynamic_lstm(
        proj, size, length=length, is_reverse=is_reverse,
        param_attr=lstm_param_attr, bias_attr=lstm_bias_attr, **kwargs)
    return hidden


def simple_gru(input, size, length=None, is_reverse=False,
               mixed_param_attr=None, gru_param_attr=None,
               gru_bias_attr=None, **kwargs):
    """fc gate projection + GRU over time (reference networks.py
    simple_gru)."""
    proj = layers.fc(input, 3 * size, num_flatten_dims=2,
                     param_attr=mixed_param_attr, bias_attr=False,
                     **kwargs)
    return layers.dynamic_gru(proj, size, length=length,
                              is_reverse=is_reverse,
                              param_attr=gru_param_attr,
                              bias_attr=gru_bias_attr, **kwargs)


def bidirectional_lstm(input, size, length=None, return_concat=True,
                       **kwargs):
    """Forward + backward LSTM over the same input; concat (or pair) of
    per-step hiddens (reference networks.py bidirectional_lstm:1005)."""
    fwd = simple_lstm(input, size, length=length, is_reverse=False,
                      **kwargs)
    bwd = simple_lstm(input, size, length=length, is_reverse=True,
                      **kwargs)
    if return_concat:
        return layers.concat([fwd, bwd], axis=2)
    return fwd, bwd


def bidirectional_gru(input, size, length=None, return_concat=True,
                      **kwargs):
    """Forward + backward GRU (reference networks.py
    bidirectional_gru)."""
    fwd = simple_gru(input, size, length=length, is_reverse=False,
                     **kwargs)
    bwd = simple_gru(input, size, length=length, is_reverse=True,
                     **kwargs)
    if return_concat:
        return layers.concat([fwd, bwd], axis=2)
    return fwd, bwd


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     length=None, **kwargs):
    """Bahdanau-style additive attention (reference networks.py
    simple_attention:1375): score_t = v . tanh(enc_proj_t + W s);
    softmax over valid steps; context = sum_t a_t * enc_t."""
    h = encoded_proj.shape[-1]
    dec_proj = layers.fc(decoder_state, h, bias_attr=False, **kwargs)
    dec_expand = layers.sequence_expand(dec_proj, encoded_proj, **kwargs)
    mix = layers.tanh(layers.elementwise_add(encoded_proj, dec_expand,
                                             **kwargs), **kwargs)
    scores = layers.fc(mix, 1, num_flatten_dims=2, bias_attr=False,
                       **kwargs)
    t = encoded_sequence.shape[1]
    scores = layers.reshape(scores, [-1, t], **kwargs)
    weights = layers.sequence_softmax(scores, length=length, **kwargs)
    weights3 = layers.reshape(weights, [-1, t, 1], **kwargs)
    weighted = layers.elementwise_mul(encoded_sequence, weights3,
                                      **kwargs)
    context = layers.reduce_sum(weighted, dim=1, **kwargs)
    return context, weights
