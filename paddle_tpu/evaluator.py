"""Stateful evaluators accumulating across batches.

Parity with reference ``fluid/evaluator.py:38,107,145`` (Evaluator base,
Accuracy, ChunkEvaluator as state-var sub-programs) and the legacy
evaluator set (SURVEY A.4). State lives in persistable scope vars updated
inside the train step (one XLA computation); ``eval()`` reads them.
"""

import numpy as np

from . import layers
from .core import unique_name
from .core.scope import global_scope
from .layer_helper import LayerHelper
from .initializer import ConstantInitializer

__all__ = ["Evaluator", "Accuracy", "ChunkEvaluator"]


class Evaluator:
    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []

    def _create_state(self, suffix, shape, dtype="float32"):
        var = self.helper.create_global_variable(
            shape=shape, dtype=dtype, persistable=True,
            name=unique_name.generate("%s.%s" % (self.helper.name,
                                                 suffix)),
            initializer=ConstantInitializer(0.0))
        self.states.append(var)
        return var

    def reset(self, executor=None, scope=None):
        scope = scope or global_scope()
        for var in self.states:
            cur = scope.find_var(var.name)
            if cur is not None:
                scope.set_var(var.name, np.zeros_like(np.asarray(cur)))

    def eval(self, executor=None, scope=None):
        raise NotImplementedError


class Accuracy(Evaluator):
    """Accumulated accuracy (reference evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        total = self._create_state("total", [], "float32")
        correct = self._create_state("correct", [], "float32")

        helper = self.helper
        topk_out = helper.create_tmp_variable(input.dtype,
                                              stop_gradient=True)
        topk_idx = helper.create_tmp_variable("int64", stop_gradient=True)
        helper.append_op(type="top_k", inputs={"X": [input.name]},
                         outputs={"Out": [topk_out.name],
                                  "Indices": [topk_idx.name]},
                         attrs={"k": k})
        acc = helper.create_tmp_variable("float32", stop_gradient=True)
        bcorrect = helper.create_tmp_variable("int64", stop_gradient=True)
        btotal = helper.create_tmp_variable("int64", stop_gradient=True)
        helper.append_op(type="accuracy",
                         inputs={"Indices": [topk_idx.name],
                                 "Label": [label.name]},
                         outputs={"Accuracy": [acc.name],
                                  "Correct": [bcorrect.name],
                                  "Total": [btotal.name]})
        # state += batch
        for state, batch in ((total, btotal), (correct, bcorrect)):
            casted = helper.create_tmp_variable("float32",
                                                stop_gradient=True)
            helper.append_op(type="cast", inputs={"X": [batch.name]},
                             outputs={"Out": [casted.name]},
                             attrs={"out_dtype": "float32"})
            helper.append_op(type="sum",
                             inputs={"X": [state.name, casted.name]},
                             outputs={"Out": [state.name]},
                             infer_shape=False)
        self.metric = acc
        self._total, self._correct = total, correct

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        total = float(np.asarray(scope.find_var(self._total.name)))
        correct = float(np.asarray(scope.find_var(self._correct.name)))
        return correct / max(total, 1.0)


class ChunkEvaluator(Evaluator):
    """Chunk-level F1 over padded tag sequences (reference
    ChunkEvaluator / chunk_eval_op) for IOB-tagged outputs."""

    def __init__(self, input, label, length, num_chunk_types,
                 chunk_scheme="IOB", **kwargs):
        super().__init__("chunk_evaluator", **kwargs)
        self.num_correct = self._create_state("correct", [], "float32")
        self.num_infer = self._create_state("infer", [], "float32")
        self.num_label = self._create_state("label", [], "float32")
        helper = self.helper
        correct = helper.create_tmp_variable("float32",
                                             stop_gradient=True)
        infer = helper.create_tmp_variable("float32", stop_gradient=True)
        lab = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="chunk_eval_counts",
                         inputs={"Inference": [input.name],
                                 "Label": [label.name],
                                 "Length": [length.name]},
                         outputs={"Correct": [correct.name],
                                  "Infer": [infer.name],
                                  "Label": [lab.name]},
                         attrs={"num_chunk_types": num_chunk_types,
                                "chunk_scheme": chunk_scheme})
        for state, batch in ((self.num_correct, correct),
                             (self.num_infer, infer),
                             (self.num_label, lab)):
            helper.append_op(type="sum",
                             inputs={"X": [state.name, batch.name]},
                             outputs={"Out": [state.name]},
                             infer_shape=False)

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        c = float(np.asarray(scope.find_var(self.num_correct.name)))
        i = float(np.asarray(scope.find_var(self.num_infer.name)))
        l = float(np.asarray(scope.find_var(self.num_label.name)))
        precision = c / i if i else 0.0
        recall = c / l if l else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return precision, recall, f1
