"""Stateful evaluators accumulating across batches.

Parity with reference ``fluid/evaluator.py:38,107,145`` (Evaluator base,
Accuracy, ChunkEvaluator as state-var sub-programs) and the legacy
evaluator set (SURVEY A.4). State lives in persistable scope vars updated
inside the train step (one XLA computation); ``eval()`` reads them.
"""

import numpy as np

from . import layers
from .core import unique_name
from .core.scope import global_scope
from .layer_helper import LayerHelper
from .initializer import ConstantInitializer

__all__ = ["Evaluator", "Accuracy", "ChunkEvaluator", "DetectionMAP",
           "Auc", "PrecisionRecall", "PnPair", "EditDistanceEvaluator",
           "SumEvaluator", "ColumnSumEvaluator", "ValuePrinter",
           "GradientPrinter", "MaxIdPrinter", "MaxFramePrinter",
           "SeqTextPrinter", "ClassificationErrorPrinter"]


class Evaluator:
    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []

    def _create_state(self, suffix, shape, dtype="float32"):
        var = self.helper.create_global_variable(
            shape=shape, dtype=dtype, persistable=True,
            name=unique_name.generate("%s.%s" % (self.helper.name,
                                                 suffix)),
            initializer=ConstantInitializer(0.0))
        self.states.append(var)
        return var

    def reset(self, executor=None, scope=None):
        scope = scope or global_scope()
        for var in self.states:
            cur = scope.find_var(var.name)
            if cur is not None:
                scope.set_var(var.name, np.zeros_like(np.asarray(cur)))

    def eval(self, executor=None, scope=None):
        raise NotImplementedError


class Accuracy(Evaluator):
    """Accumulated accuracy (reference evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        total = self._create_state("total", [], "float32")
        correct = self._create_state("correct", [], "float32")

        helper = self.helper
        topk_out = helper.create_tmp_variable(input.dtype,
                                              stop_gradient=True)
        topk_idx = helper.create_tmp_variable("int64", stop_gradient=True)
        helper.append_op(type="top_k", inputs={"X": [input.name]},
                         outputs={"Out": [topk_out.name],
                                  "Indices": [topk_idx.name]},
                         attrs={"k": k})
        acc = helper.create_tmp_variable("float32", stop_gradient=True)
        bcorrect = helper.create_tmp_variable("int64", stop_gradient=True)
        btotal = helper.create_tmp_variable("int64", stop_gradient=True)
        helper.append_op(type="accuracy",
                         inputs={"Indices": [topk_idx.name],
                                 "Label": [label.name]},
                         outputs={"Accuracy": [acc.name],
                                  "Correct": [bcorrect.name],
                                  "Total": [btotal.name]})
        # state += batch
        for state, batch in ((total, btotal), (correct, bcorrect)):
            casted = helper.create_tmp_variable("float32",
                                                stop_gradient=True)
            helper.append_op(type="cast", inputs={"X": [batch.name]},
                             outputs={"Out": [casted.name]},
                             attrs={"out_dtype": "float32"})
            helper.append_op(type="sum",
                             inputs={"X": [state.name, casted.name]},
                             outputs={"Out": [state.name]},
                             infer_shape=False)
        self.metric = acc
        self._total, self._correct = total, correct

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        total = float(np.asarray(scope.find_var(self._total.name)))
        correct = float(np.asarray(scope.find_var(self._correct.name)))
        return correct / max(total, 1.0)


class ChunkEvaluator(Evaluator):
    """Chunk-level F1 over padded tag sequences (reference
    ChunkEvaluator / chunk_eval_op) for IOB-tagged outputs."""

    def __init__(self, input, label, length, num_chunk_types,
                 chunk_scheme="IOB", **kwargs):
        super().__init__("chunk_evaluator", **kwargs)
        self.num_correct = self._create_state("correct", [], "float32")
        self.num_infer = self._create_state("infer", [], "float32")
        self.num_label = self._create_state("label", [], "float32")
        helper = self.helper
        correct = helper.create_tmp_variable("float32",
                                             stop_gradient=True)
        infer = helper.create_tmp_variable("float32", stop_gradient=True)
        lab = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="chunk_eval_counts",
                         inputs={"Inference": [input.name],
                                 "Label": [label.name],
                                 "Length": [length.name]},
                         outputs={"Correct": [correct.name],
                                  "Infer": [infer.name],
                                  "Label": [lab.name]},
                         attrs={"num_chunk_types": num_chunk_types,
                                "chunk_scheme": chunk_scheme})
        for state, batch in ((self.num_correct, correct),
                             (self.num_infer, infer),
                             (self.num_label, lab)):
            helper.append_op(type="sum",
                             inputs={"X": [state.name, batch.name]},
                             outputs={"Out": [state.name]},
                             infer_shape=False)

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        c = float(np.asarray(scope.find_var(self.num_correct.name)))
        i = float(np.asarray(scope.find_var(self.num_infer.name)))
        l = float(np.asarray(scope.find_var(self.num_label.name)))
        precision = c / i if i else 0.0
        recall = c / l if l else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return precision, recall, f1


class DetectionMAP:
    """Host-side mAP evaluator (reference
    ``gserver/evaluators/DetectionMAPEvaluator.cpp``; the reference also
    computes mAP on CPU outside the device graph). Feed per batch:
    ``update(detections, gt_boxes, gt_labels, gt_counts)`` with
    detections [N, K, 6] rows (label, score, x1, y1, x2, y2), label -1
    = empty, and padded ground truth. ``eval()`` returns mAP over the
    accumulated stream (11-point interpolation by default, or
    'integral')."""

    def __init__(self, num_classes, overlap_threshold=0.5,
                 ap_version="11point", background_label=0):
        self.num_classes = num_classes
        self.overlap = overlap_threshold
        self.ap_version = ap_version
        self.background = background_label
        self.reset()

    def reset(self, executor=None, scope=None):
        # per class: list of (score, tp) + GT count
        self._dets = {c: [] for c in range(self.num_classes)}
        self._n_gt = {c: 0 for c in range(self.num_classes)}

    @staticmethod
    def _iou(a, b):
        ax1, ay1, ax2, ay2 = a
        bx1, by1, bx2, by2 = b
        ix = max(0.0, min(ax2, bx2) - max(ax1, bx1))
        iy = max(0.0, min(ay2, by2) - max(ay1, by1))
        inter = ix * iy
        ua = max(0.0, ax2 - ax1) * max(0.0, ay2 - ay1) + \
            max(0.0, bx2 - bx1) * max(0.0, by2 - by1) - inter
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gt_boxes, gt_labels, gt_counts):
        detections = np.asarray(detections)
        gt_boxes = np.asarray(gt_boxes)
        gt_labels = np.asarray(gt_labels).reshape(gt_boxes.shape[0], -1)
        gt_counts = np.asarray(gt_counts).reshape(-1)
        for n in range(detections.shape[0]):
            cnt = int(gt_counts[n])
            # tolerate padded / out-of-range GT labels like detection
            # rows (label -1 = empty)
            gts = [(int(gt_labels[n, g]), gt_boxes[n, g])
                   for g in range(cnt) if int(gt_labels[n, g]) >= 0]
            for c in set(l for l, _ in gts):
                self._n_gt[c] = self._n_gt.get(c, 0) + \
                    sum(1 for l, _ in gts if l == c)
            used = [False] * cnt
            rows = [r for r in detections[n] if r[0] >= 0]
            rows.sort(key=lambda r: -r[1])
            for r in rows:
                c = int(r[0])
                best, best_g = 0.0, -1
                for g, (gl, gb) in enumerate(gts):
                    if gl != c or used[g]:
                        continue
                    v = self._iou(r[2:6], gb)
                    if v > best:
                        best, best_g = v, g
                tp = best >= self.overlap and best_g >= 0
                if tp:
                    used[best_g] = True
                self._dets.setdefault(c, []).append((float(r[1]), tp))

    def _ap(self, recs, precs):
        if self.ap_version == "integral":
            ap, prev_r = 0.0, 0.0
            for r, p in zip(recs, precs):
                ap += (r - prev_r) * p
                prev_r = r
            return ap
        ap = 0.0
        for t in np.arange(0.0, 1.01, 0.1):
            ps = [p for r, p in zip(recs, precs) if r >= t]
            ap += (max(ps) if ps else 0.0) / 11.0
        return ap

    def eval(self, executor=None, scope=None):
        aps = []
        for c in range(self.num_classes):
            if c == self.background or self._n_gt.get(c, 0) == 0:
                continue
            dets = sorted(self._dets.get(c, []), key=lambda d: -d[0])
            tp_cum, recs, precs = 0, [], []
            for i, (_, tp) in enumerate(dets):
                tp_cum += int(tp)
                recs.append(tp_cum / self._n_gt[c])
                precs.append(tp_cum / (i + 1))
            aps.append(self._ap(recs, precs) if dets else 0.0)
        return float(np.mean(aps)) if aps else 0.0


class Auc(Evaluator):
    """Accumulated ROC-AUC (reference auc_op.cc accumulation +
    gserver rankauc evaluator capability): per-threshold TP/FP/FN/TN
    counts accumulate across batches; eval() integrates the ROC."""

    def __init__(self, input, label, num_thresholds=200, **kwargs):
        super().__init__("auc_evaluator", **kwargs)
        self.num_thresholds = num_thresholds
        self._counts = self._create_state("counts",
                                          [num_thresholds, 4], "float32")
        helper = self.helper
        auc_out = helper.create_tmp_variable("float32",
                                             stop_gradient=True)
        counts = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="auc",
                         inputs={"Out": [input.name],
                                 "Label": [label.name]},
                         outputs={"AUC": [auc_out.name],
                                  "StatCounts": [counts.name]},
                         attrs={"num_thresholds": num_thresholds})
        helper.append_op(type="sum",
                         inputs={"X": [self._counts.name, counts.name]},
                         outputs={"Out": [self._counts.name]},
                         infer_shape=False)
        self.metric = auc_out

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        c = np.asarray(scope.find_var(self._counts.name))
        tp, fp, fn, tn = c[:, 0], c[:, 1], c[:, 2], c[:, 3]
        tpr = tp / np.maximum(tp + fn, 1e-12)
        fpr = fp / np.maximum(fp + tn, 1e-12)
        return float(abs(np.sum((fpr[:-1] - fpr[1:]) *
                                (tpr[:-1] + tpr[1:]) / 2.0)))


class PrecisionRecall(Evaluator):
    """Accumulated per-class precision/recall/F1 (reference
    precision_recall_op.cc states + gserver precision_recall
    evaluator). eval() returns 6 numbers: macro then micro (p, r, f1)."""

    def __init__(self, input, label, num_classes, **kwargs):
        super().__init__("precision_recall_evaluator", **kwargs)
        self.num_classes = num_classes
        self._states = self._create_state("tp_fp_fn", [num_classes, 3],
                                          "float32")
        helper = self.helper
        topk_out = helper.create_tmp_variable(input.dtype,
                                              stop_gradient=True)
        topk_idx = helper.create_tmp_variable("int64", stop_gradient=True)
        helper.append_op(type="top_k", inputs={"X": [input.name]},
                         outputs={"Out": [topk_out.name],
                                  "Indices": [topk_idx.name]},
                         attrs={"k": 1})
        batch = helper.create_tmp_variable("float32", stop_gradient=True)
        accum = helper.create_tmp_variable("float32", stop_gradient=True)
        states = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="precision_recall",
                         inputs={"MaxProbs": [topk_out.name],
                                 "Indices": [topk_idx.name],
                                 "Labels": [label.name]},
                         outputs={"BatchMetrics": [batch.name],
                                  "AccumMetrics": [accum.name],
                                  "AccumStatesInfo": [states.name]},
                         attrs={"class_number": num_classes})
        helper.append_op(type="sum",
                         inputs={"X": [self._states.name, states.name]},
                         outputs={"Out": [self._states.name]},
                         infer_shape=False)
        self.metric = batch

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        s = np.asarray(scope.find_var(self._states.name))
        tp, fp, fn = s[:, 0], s[:, 1], s[:, 2]
        p = tp / np.maximum(tp + fp, 1e-12)
        r = tp / np.maximum(tp + fn, 1e-12)
        f1 = 2 * p * r / np.maximum(p + r, 1e-12)
        mi_p = tp.sum() / max(float((tp + fp).sum()), 1e-12)
        mi_r = tp.sum() / max(float((tp + fn).sum()), 1e-12)
        mi_f = 2 * mi_p * mi_r / max(mi_p + mi_r, 1e-12)
        return (float(p.mean()), float(r.mean()), float(f1.mean()),
                float(mi_p), float(mi_r), float(mi_f))


class PnPair(Evaluator):
    """Accumulated positive-negative pair ranking ratio (reference
    positive_negative_pair_op / gserver pnpair evaluator)."""

    def __init__(self, score, label, query_id, **kwargs):
        super().__init__("pnpair_evaluator", **kwargs)
        self._pos = self._create_state("pos", [], "float32")
        self._neg = self._create_state("neg", [], "float32")
        helper = self.helper
        pos = helper.create_tmp_variable("float32", stop_gradient=True)
        neg = helper.create_tmp_variable("float32", stop_gradient=True)
        neu = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="positive_negative_pair",
                         inputs={"Score": [score.name],
                                 "Label": [label.name],
                                 "QueryID": [query_id.name]},
                         outputs={"PositivePair": [pos.name],
                                  "NegativePair": [neg.name],
                                  "NeutralPair": [neu.name]})
        for state, batch in ((self._pos, pos), (self._neg, neg)):
            helper.append_op(type="sum",
                             inputs={"X": [state.name, batch.name]},
                             outputs={"Out": [state.name]},
                             infer_shape=False)

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        pos = float(np.asarray(scope.find_var(self._pos.name)))
        neg = float(np.asarray(scope.find_var(self._neg.name)))
        return pos / max(neg, 1e-12)


class EditDistanceEvaluator(Evaluator):
    """Accumulated mean edit distance (reference ctc_error evaluator /
    edit_distance_op accumulation)."""

    def __init__(self, hyps, hyps_length, refs, refs_length,
                 normalized=False, **kwargs):
        super().__init__("edit_distance_evaluator", **kwargs)
        self._total = self._create_state("total", [], "float32")
        self._count = self._create_state("count", [], "float32")
        helper = self.helper
        dist = helper.create_tmp_variable("float32", stop_gradient=True)
        seq_num = helper.create_tmp_variable("float32",
                                             stop_gradient=True)
        helper.append_op(type="edit_distance",
                         inputs={"Hyps": [hyps.name],
                                 "HypsLength": [hyps_length.name],
                                 "Refs": [refs.name],
                                 "RefsLength": [refs_length.name]},
                         outputs={"Out": [dist.name],
                                  "SequenceNum": [seq_num.name]},
                         attrs={"normalized": normalized})
        summed = helper.create_tmp_variable("float32",
                                            stop_gradient=True)
        cnt = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="reduce_sum", inputs={"X": [dist.name]},
                         outputs={"Out": [summed.name]},
                         attrs={"dim": None, "keep_dim": False,
                                "reduce_all": True})
        helper.append_op(type="cast", inputs={"X": [seq_num.name]},
                         outputs={"Out": [cnt.name]},
                         attrs={"out_dtype": "float32"})
        for state, batch in ((self._total, summed),
                             (self._count, cnt)):
            helper.append_op(type="sum",
                             inputs={"X": [state.name, batch.name]},
                             outputs={"Out": [state.name]},
                             infer_shape=False)
        self.metric = dist

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        total = float(np.asarray(scope.find_var(self._total.name)))
        n_seq = float(np.asarray(scope.find_var(self._count.name)))
        return total / max(n_seq, 1.0)


class SumEvaluator(Evaluator):
    """Accumulated sum of the input, reported per sample (reference
    SumEvaluator, Evaluator.cpp:160-270; config api sum_evaluator).
    Optional ``weight`` multiplies per-sample rows and divides the
    sample count, as the reference's weighted mode."""

    _NAME = "sum_evaluator"
    _REDUCE_DIM = None   # full sum; ColumnSum keeps columns

    def __init__(self, input, weight=None, **kwargs):
        super().__init__(self._NAME, **kwargs)
        shape = [] if self._REDUCE_DIM is None else [input.shape[-1]]
        total = self._create_state("sum", shape, "float32")
        samples = self._create_state("samples", [], "float32")
        x = input
        if weight is not None:
            x = layers.elementwise_mul(input, weight)
        bsum = layers.reduce_sum(x) if self._REDUCE_DIM is None \
            else layers.reduce_sum(x, dim=self._REDUCE_DIM)
        bn = layers.reduce_sum(weight) if weight is not None else \
            layers.reduce_sum(
                layers.fill_constant_batch_size_like(
                    input, [-1], "float32", 1.0))
        for state, batch in ((total, bsum), (samples, bn)):
            self.helper.append_op(
                type="sum", inputs={"X": [state.name, batch.name]},
                outputs={"Out": [state.name]}, infer_shape=False)
        self._sum, self._samples = total, samples

    def eval(self, executor=None, scope=None):
        scope = scope or global_scope()
        s = np.asarray(scope.find_var(self._sum.name))
        n = float(np.asarray(scope.find_var(self._samples.name)))
        out = s / max(n, 1.0)
        return float(out) if out.ndim == 0 else out


class ColumnSumEvaluator(SumEvaluator):
    """Per-column accumulated mean (reference ColumnSumEvaluator,
    Evaluator.cpp:273-360; config api column_sum_evaluator).
    ``col_idx``: report one column, or None for the full vector."""

    _NAME = "column_sum_evaluator"
    _REDUCE_DIM = 0

    def __init__(self, input, weight=None, col_idx=None, **kwargs):
        super().__init__(input, weight=weight, **kwargs)
        self.col_idx = col_idx

    def eval(self, executor=None, scope=None):
        out = super().eval(executor, scope)
        return float(out[self.col_idx]) if self.col_idx is not None \
            else out


# ---- printer evaluators ---------------------------------------------
# The reference's debugging surface (Evaluator.cpp:1018-1357): each
# prints its subject per batch. Temporaries never materialize in a
# Scope here (SURVEY north star), so printers attach a print op INSIDE
# the step — output appears each step via jax.debug.print (flush with
# jax.effects_barrier()), rather than at eval() time.

class _Printer(Evaluator):
    def eval(self, executor=None, scope=None):
        return None


class ValuePrinter(_Printer):
    """Print layer outputs (value_printer_evaluator)."""

    def __init__(self, *inputs, **kwargs):
        super().__init__("value_printer", **kwargs)
        for v in inputs:
            layers.Print(v, message="value_printer %s" % v.name)


class GradientPrinter(_Printer):
    """Print a variable's gradient (gradient_printer_evaluator).
    Construct AFTER optimizer.minimize so the @GRAD vars exist."""

    def __init__(self, *inputs, **kwargs):
        super().__init__("gradient_printer", **kwargs)
        block = self.helper.main_program.global_block()
        for v in inputs:
            # multi-consumer vars carry per-consumer contributions in
            # name@GRAD, name@GRAD@1, ... with the TRUE sum in
            # name@GRAD@SUM (core/backward.py) — print the sum if the
            # var has one
            gname = v.name + "@GRAD"
            gsum = gname + "@SUM"
            if block.has_var(gsum):
                gname = gsum
            elif not block.has_var(gname):
                raise ValueError(
                    "no gradient recorded for %r — construct "
                    "GradientPrinter after minimize()" % v.name)
            layers.Print(block.var(gname),
                         message="gradient_printer %s" % gname)


class MaxIdPrinter(_Printer):
    """Print per-row argmax ids (maxid_printer_evaluator)."""

    def __init__(self, input, **kwargs):
        super().__init__("maxid_printer", **kwargs)
        ids = layers.argmax(input, axis=-1)
        layers.Print(ids, message="maxid_printer %s" % input.name)


class MaxFramePrinter(_Printer):
    """Print, per sequence, the frame (time step) with the max value
    (maxframe_printer_evaluator)."""

    def __init__(self, input, **kwargs):
        super().__init__("maxframe_printer", **kwargs)
        score = layers.reduce_max(input, dim=-1)
        frame = layers.argmax(score, axis=-1)
        layers.Print(frame, message="maxframe_printer %s" % input.name)


class SeqTextPrinter(_Printer):
    """Print generated id sequences (seq_text_printer_evaluator). The
    reference maps ids through a dict file on the host; here ids print
    in-step and ``to_text(ids, vocab)`` does the host-side join."""

    def __init__(self, input, **kwargs):
        super().__init__("seq_text_printer", **kwargs)
        layers.Print(input, message="seq_text_printer %s" % input.name)

    @staticmethod
    def to_text(ids, vocab, eos_id=1):
        out = []
        for row in np.asarray(ids):
            toks = []
            for t in row:
                if t == eos_id:
                    break
                toks.append(vocab[int(t)] if int(t) < len(vocab)
                            else "<unk>")
            out.append(" ".join(toks))
        return out


class ClassificationErrorPrinter(_Printer):
    """Print per-sample 0/1 classification error
    (classification_error_printer_evaluator)."""

    def __init__(self, input, label, **kwargs):
        super().__init__("classification_error_printer", **kwargs)
        pred = layers.argmax(input, axis=-1)
        lbl = layers.reshape(label, [-1])
        err = layers.cast(
            layers.control_flow.equal(pred, lbl), "float32")
        err = layers.scale(err, scale=-1.0, bias=1.0)
        layers.Print(err, message="classification_error_printer")
