"""Parameter initializers.

Parity with reference ``python/paddle/v2/fluid/initializer.py`` (Constant /
Uniform / Normal / Xavier / MSRA as fill ops appended to the startup
program). Same design here: an initializer appends ONE op to the startup
program, so initialization itself is a jitted XLA computation.
"""

import numpy as np

__all__ = ["Constant", "Uniform", "Normal", "Xavier", "MSRA",
           "ConstantInitializer", "UniformInitializer", "NormalInitializer",
           "XavierInitializer", "MSRAInitializer",
           "NumpyArrayInitializer"]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return (1, shape[0] if shape else 1)
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        # conv filters are [out_c, in_c, *spatial]; fc weights [in, out]
        if len(shape) > 2:
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
        else:
            fan_in, fan_out = shape[0], shape[1]
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": float(self.value)},
                        infer_shape=False)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": float(self.low),
                               "max": float(self.high), "seed": self.seed},
                        infer_shape=False)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc),
                               "std": float(self.scale), "seed": self.seed},
                        infer_shape=False)


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out = fan_in, fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / (fi + fo)))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fi))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / fi))
            NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    """Initialize from a literal array (reference fluid
    NumpyArrayInitializer / assign_value_op)."""

    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            "assign_value", outputs={"Out": [var.name]},
            attrs={"shape": list(self.value.shape),
                   "dtype": var.dtype,
                   "values": self.value.ravel().tolist()},
            infer_shape=False)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
