"""Optimizer classes emitting optimizer ops + accumulators.

Parity with reference ``python/paddle/v2/fluid/optimizer.py`` (SGD/Momentum/
Adagrad/Adam/Adamax/DecayedAdagrad + global_step/minimize) and the legacy
``FirstOrderOptimizer.h`` family (AdaDelta, RMSProp, Ftrl added). The emitted
update ops join fwd/bwd in the same block, so Executor.run does
forward+backward+update as ONE donated XLA computation — the TPU answer to
the reference's separate updater stage (``TrainerInternal.cpp:66-171``).
"""

import numpy as np

from .core import unique_name
from .core.framework import default_main_program, default_startup_program
from .core.backward import append_backward
from .initializer import ConstantInitializer
from .regularizer import append_regularization_ops

__all__ = ["SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
           "AdaDelta", "RMSProp", "Ftrl", "SGDOptimizer",
           "MomentumOptimizer", "AdagradOptimizer", "AdamOptimizer",
           "AdamaxOptimizer", "DecayedAdagradOptimizer",
           "AdaDeltaOptimizer", "RMSPropOptimizer", "FtrlOptimizer",
           "Optimizer", "ModelAverage"]


class Optimizer:
    def __init__(self, learning_rate=0.001, regularization=None,
                 global_step=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._global_step = global_step
        self._lr_var = None
        self._accumulators = {}

    # -- plumbing ------------------------------------------------------------
    def _get_main(self, loss):
        return loss.block.program

    def _create_lr_var(self, main, startup):
        if self._lr_var is not None:
            return self._lr_var
        if not isinstance(self._learning_rate, (int, float)):
            # a Variable (e.g. produced by a lr-schedule subgraph)
            self._lr_var = self._learning_rate
            return self._lr_var
        name = unique_name.generate("learning_rate")
        block = main.global_block()
        var = block.create_var(name=name, shape=[1], dtype="float32",
                               persistable=True, stop_gradient=True)
        svar = startup.global_block().create_var(
            name=name, shape=[1], dtype="float32", persistable=True)
        ConstantInitializer(float(self._learning_rate))(
            svar, startup.global_block())
        self._lr_var = var
        return var

    def _lr_for_param(self, main, param):
        mult = param.optimize_attr.get("learning_rate", 1.0)
        if mult == 1.0:
            return self._lr_var
        block = main.global_block()
        out = block.create_var(
            name=unique_name.generate("%s.lr" % param.name), shape=[1],
            dtype="float32", stop_gradient=True)
        block.append_op("scale", inputs={"X": [self._lr_var.name]},
                        outputs={"Out": [out.name]},
                        attrs={"scale": float(mult)})
        return out

    def _add_accumulator(self, name, param, main, startup, fill_value=0.0,
                         shape=None):
        key = (name, param.name)
        if key in self._accumulators:
            return self._accumulators[key]
        shape = list(shape if shape is not None else param.shape)
        vname = unique_name.generate("%s_%s_acc" % (param.name, name))
        block = main.global_block()
        # A distributed embedding table's row-shaped slots (Adam
        # moments etc.) are registered alongside it, so DistStrategy
        # row-shards them by the same rule and checkpoint reshard
        # re-permutes them with the table. Scalar slots (beta powers,
        # shape [1]) stay replicated.
        tables = getattr(main, "_dist_embeddings", None)
        if tables is not None and param.name in tables and \
                tables[param.name].get("slot_of") is None and \
                shape and shape[0] == tables[param.name]["padded"]:
            info = tables[param.name]
            tables[vname] = {"vocab": info["vocab"],
                             "padded": info["padded"],
                             "dim": info["dim"], "slot_of": param.name}
        var = block.create_var(name=vname, shape=shape, dtype=param.dtype,
                               persistable=True, stop_gradient=True)
        svar = startup.global_block().create_var(
            name=vname, shape=shape, dtype=param.dtype, persistable=True)
        ConstantInitializer(fill_value)(svar, startup.global_block())
        self._accumulators[key] = var
        return var

    # -- public --------------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        main = self._get_main(loss)
        startup = startup_program or default_startup_program()
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      main, startup)
        if self._global_step is not None:
            loss.block.append_op(
                "increment", inputs={"X": [self._global_step.name]},
                outputs={"Out": [self._global_step.name]},
                attrs={"step": 1.0}, infer_shape=False)
        return optimize_ops, params_grads

    def _create_optimization_pass(self, params_grads, loss, main, startup):
        self._create_lr_var(main, startup)
        ops = []
        for param, grad in params_grads:
            if grad is None:
                continue
            self._check_sparse(grad)
            ops.append(self._append_optimize_op(main, startup, param, grad))
        return ops

    def _append_optimize_op(self, main, startup, param, grad):
        raise NotImplementedError

    @staticmethod
    def _grad_inputs(grad):
        """Grad input slots; sparse (SelectedRows-style) grads add Rows."""
        ins = {"Grad": [grad.name]}
        rows = getattr(grad, "selected_rows", None)
        if rows is not None:
            ins["Rows"] = [rows.name]
        return ins

    _SPARSE_CAPABLE = False

    def _check_sparse(self, grad):
        if getattr(grad, "selected_rows", None) is not None and \
                not self._SPARSE_CAPABLE:
            raise NotImplementedError(
                "%s has no sparse (SelectedRows) update rule — use "
                "SGD/Momentum/Adagrad/Adam for is_sparse embeddings"
                % type(self).__name__)


class SGD(Optimizer):
    _SPARSE_CAPABLE = True

    def _append_optimize_op(self, main, startup, param, grad):
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "sgd",
            inputs=dict(self._grad_inputs(grad), Param=[param.name],
                        LearningRate=[lr.name]),
            outputs={"ParamOut": [param.name]}, infer_shape=False)


class Momentum(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    _SPARSE_CAPABLE = True

    def _append_optimize_op(self, main, startup, param, grad):
        vel = self._add_accumulator("velocity", param, main, startup)
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "momentum",
            inputs=dict(self._grad_inputs(grad), Param=[param.name],
                        Velocity=[vel.name], LearningRate=[lr.name]),
            outputs={"ParamOut": [param.name], "VelocityOut": [vel.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
            infer_shape=False)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon

    _SPARSE_CAPABLE = True

    def _append_optimize_op(self, main, startup, param, grad):
        moment = self._add_accumulator("moment", param, main, startup)
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "adagrad",
            inputs=dict(self._grad_inputs(grad), Param=[param.name],
                        Moment=[moment.name], LearningRate=[lr.name]),
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name]},
            attrs={"epsilon": self._epsilon}, infer_shape=False)


class Adam(Optimizer):
    _SPARSE_CAPABLE = True  # lazy adam on touched rows

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, main, startup, param, grad):
        m1 = self._add_accumulator("moment1", param, main, startup)
        m2 = self._add_accumulator("moment2", param, main, startup)
        b1p = self._add_accumulator("beta1_pow", param, main, startup,
                                    fill_value=self._beta1, shape=[1])
        b2p = self._add_accumulator("beta2_pow", param, main, startup,
                                    fill_value=self._beta2, shape=[1])
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "adam",
            inputs=dict(self._grad_inputs(grad), Param=[param.name],
                    Moment1=[m1.name], Moment2=[m2.name],
                    Beta1Pow=[b1p.name], Beta2Pow=[b2p.name],
                    LearningRate=[lr.name]),
            outputs={"ParamOut": [param.name], "Moment1Out": [m1.name],
                     "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
                     "Beta2PowOut": [b2p.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon}, infer_shape=False)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, main, startup, param, grad):
        m = self._add_accumulator("moment", param, main, startup)
        inf = self._add_accumulator("inf_norm", param, main, startup)
        b1p = self._add_accumulator("beta1_pow", param, main, startup,
                                    fill_value=self._beta1, shape=[1])
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "adamax",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Moment": [m.name], "InfNorm": [inf.name],
                    "Beta1Pow": [b1p.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "MomentOut": [m.name],
                     "InfNormOut": [inf.name], "Beta1PowOut": [b1p.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon}, infer_shape=False)


class DecayedAdagrad(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay, self._epsilon = decay, epsilon

    def _append_optimize_op(self, main, startup, param, grad):
        moment = self._add_accumulator("moment", param, main, startup)
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "decayed_adagrad",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Moment": [moment.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
            infer_shape=False)


class AdaDelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon

    def _append_optimize_op(self, main, startup, param, grad):
        g2 = self._add_accumulator("avg_squared_grad", param, main, startup)
        u2 = self._add_accumulator("avg_squared_update", param, main,
                                   startup)
        return main.global_block().append_op(
            "adadelta",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "AvgSquaredGrad": [g2.name],
                    "AvgSquaredUpdate": [u2.name]},
            outputs={"ParamOut": [param.name], "AvgSquaredGradOut":
                     [g2.name], "AvgSquaredUpdateOut": [u2.name]},
            attrs={"rho": self._rho, "epsilon": self._epsilon},
            infer_shape=False)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, decay=0.9, momentum=0.0,
                 epsilon=1e-10, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay, self._momentum, self._epsilon = decay, momentum, epsilon

    def _append_optimize_op(self, main, startup, param, grad):
        ms = self._add_accumulator("mean_square", param, main, startup)
        mom = self._add_accumulator("moment", param, main, startup)
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "rmsprop",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "MeanSquare": [ms.name], "Moment": [mom.name],
                    "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "MeanSquareOut": [ms.name],
                     "MomentOut": [mom.name]},
            attrs={"decay": self._decay, "momentum": self._momentum,
                   "epsilon": self._epsilon}, infer_shape=False)


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _append_optimize_op(self, main, startup, param, grad):
        sq = self._add_accumulator("squared", param, main, startup)
        lin = self._add_accumulator("linear", param, main, startup)
        lr = self._lr_for_param(main, param)
        return main.global_block().append_op(
            "ftrl",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "SquaredAccumulator": [sq.name],
                    "LinearAccumulator": [lin.name],
                    "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power}, infer_shape=False)


# fluid-style aliases
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DecayedAdagradOptimizer = DecayedAdagrad
AdaDeltaOptimizer = AdaDelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl


def append_gradient_clip_ops(params_grads):
    """Apply per-parameter gradient_clip attrs (reference clip.py:102)."""
    from .clip import append_gradient_clip_ops as _impl
    return _impl(params_grads)


class ModelAverage:
    """Parameter averaging for evaluation (reference
    ``paddle/parameter/AverageOptimizer.h:23`` / fluid ModelAverage):
    accumulation ops are appended to the main program (in the same
    donated step as the optimizer update), and ``apply()`` swaps the
    averaged parameters in around evaluation, ``restore()`` (or leaving
    the context) swaps the trained values back.

    Differences from the reference, by design: the window is
    "since construction or the last reset_window()" — the reference's
    rolling min/max window bookkeeping collapses to an explicit reset,
    which composes with the one-XLA-step executor without in-graph
    conditionals.
    """

    def __init__(self, main_program=None, startup_program=None,
                 parameter_list=None):
        main = main_program or default_main_program()
        startup = startup_program or default_startup_program()
        block = main.global_block()
        sblock = startup.global_block()
        params = block.all_parameters()
        if parameter_list is not None:
            wanted = {p if isinstance(p, str) else p.name
                      for p in parameter_list}
            params = [p for p in params if p.name in wanted]
        self._param_names = [p.name for p in params]
        self._sums = {}
        cname = unique_name.generate("model_average_count")
        cvar = block.create_var(name=cname, shape=[1], dtype="float32",
                                persistable=True, stop_gradient=True)
        svar = sblock.create_var(name=cname, shape=[1], dtype="float32",
                                 persistable=True)
        ConstantInitializer(0.0)(svar, sblock)
        block.append_op("increment", inputs={"X": [cname]},
                        outputs={"Out": [cname]}, attrs={"step": 1.0},
                        infer_shape=False)
        self._count_name = cname
        for p in params:
            sname = unique_name.generate("%s_avg_sum" % p.name)
            # accumulate in f32 regardless of the parameter dtype: a
            # bf16 running sum loses the window's low-order contributions
            var = block.create_var(name=sname, shape=list(p.shape),
                                   dtype="float32", persistable=True,
                                   stop_gradient=True)
            sv = sblock.create_var(name=sname, shape=list(p.shape),
                                   dtype="float32", persistable=True)
            ConstantInitializer(0.0)(sv, sblock)
            # runs after the optimizer's update of p in the same block
            block.append_op("elementwise_add",
                            inputs={"X": [sname], "Y": [p.name]},
                            outputs={"Out": [sname]}, infer_shape=False)
            self._sums[p.name] = sname
        self._backup = None

    def apply(self, scope=None):
        """Swap averaged parameter values in (context manager)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._swap_in(scope)
            try:
                yield
            finally:
                self.restore(scope)
        return _ctx()

    def _swap_in(self, scope=None):
        from .core.scope import global_scope
        scope = scope or global_scope()
        count = float(np.asarray(scope.find_var(
            self._count_name)).ravel()[0])
        if count <= 0:
            raise RuntimeError("ModelAverage.apply before any step ran")
        self._backup = {}
        for pname in self._param_names:
            self._backup[pname] = scope.find_var(pname)
            avg = np.asarray(scope.find_var(self._sums[pname])) / count
            # swap in with the parameter's own dtype so the compiled
            # step's feed signature is unchanged on the next run
            pdtype = np.asarray(scope.find_var(pname)).dtype
            scope.set_var(pname, avg.astype(pdtype, copy=False))

    def restore(self, scope=None):
        from .core.scope import global_scope
        scope = scope or global_scope()
        if self._backup is None:
            return
        for pname, val in self._backup.items():
            scope.set_var(pname, val)
        self._backup = None

    def reset_window(self, scope=None):
        """Restart accumulation (the window boundary)."""
        from .core.scope import global_scope
        scope = scope or global_scope()
        scope.set_var(self._count_name,
                      np.zeros([1], dtype=np.float32))
        for pname in self._param_names:
            scope.set_var(self._sums[pname],
                          np.zeros(
                              np.asarray(scope.find_var(pname)).shape,
                              dtype=np.float32))
