"""Learning-rate schedules.

Parity with the legacy scheduler set (``paddle/parameter/
LearningRateScheduler.cpp``: constant / exp / discexp / poly / caltech /
pass-manual / linear-warmup) — host-side objects that update the
optimizer's persistable learning-rate variable in the scope each step, the
TPU analog of the legacy per-batch lr computation.
"""

import bisect

import numpy as np

from .core.scope import global_scope

__all__ = ["LRScheduler", "ExponentialDecay", "InverseTimeDecay",
           "PolynomialDecay", "PiecewiseDecay", "LinearWarmup",
           "NaturalExpDecay"]


class LRScheduler:
    def __init__(self, optimizer, base_lr=None):
        self.optimizer = optimizer
        self.base_lr = base_lr if base_lr is not None else \
            optimizer._learning_rate
        self.step_num = 0

    def get_lr(self, step):
        raise NotImplementedError

    def step(self, scope=None):
        """Advance one step and write the new lr into the scope."""
        self.step_num += 1
        lr = float(self.get_lr(self.step_num))
        scope = scope or global_scope()
        var = self.optimizer._lr_var
        if var is None:
            raise RuntimeError("optimizer.minimize must run before "
                               "scheduler.step")
        scope.set_var(var.name, np.asarray([lr], dtype="float32"))
        return lr


class ExponentialDecay(LRScheduler):
    def __init__(self, optimizer, decay_steps, decay_rate,
                 staircase=False, **kw):
        super().__init__(optimizer, **kw)
        self.decay_steps, self.decay_rate = decay_steps, decay_rate
        self.staircase = staircase

    def get_lr(self, step):
        e = step / self.decay_steps
        if self.staircase:
            e = np.floor(e)
        return self.base_lr * (self.decay_rate ** e)


class NaturalExpDecay(ExponentialDecay):
    def get_lr(self, step):
        e = step / self.decay_steps
        if self.staircase:
            e = np.floor(e)
        return self.base_lr * np.exp(-self.decay_rate * e)


class InverseTimeDecay(ExponentialDecay):
    def get_lr(self, step):
        e = step / self.decay_steps
        if self.staircase:
            e = np.floor(e)
        return self.base_lr / (1.0 + self.decay_rate * e)


class PolynomialDecay(LRScheduler):
    def __init__(self, optimizer, decay_steps, end_lr=1e-4, power=1.0,
                 cycle=False, **kw):
        super().__init__(optimizer, **kw)
        self.decay_steps, self.end_lr = decay_steps, end_lr
        self.power, self.cycle = power, cycle

    def get_lr(self, step):
        if self.cycle:
            div = max(1.0, np.ceil(step / self.decay_steps))
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        frac = (1.0 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class PiecewiseDecay(LRScheduler):
    def __init__(self, optimizer, boundaries, values):
        super().__init__(optimizer)
        assert len(values) == len(boundaries) + 1
        self.boundaries, self.values = list(boundaries), list(values)

    def get_lr(self, step):
        return self.values[bisect.bisect_right(self.boundaries, step)]


class LinearWarmup(LRScheduler):
    """Warm up linearly then hand off to an inner scheduler (or constant)."""

    def __init__(self, optimizer, warmup_steps, start_lr=0.0, inner=None,
                 **kw):
        super().__init__(optimizer, **kw)
        self.warmup_steps, self.start_lr = warmup_steps, start_lr
        self.inner = inner

    def get_lr(self, step):
        if step < self.warmup_steps:
            frac = step / self.warmup_steps
            return self.start_lr + (self.base_lr - self.start_lr) * frac
        if self.inner is not None:
            return self.inner.get_lr(step - self.warmup_steps)
        return self.base_lr
