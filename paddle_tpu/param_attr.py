"""ParamAttr — per-parameter config (reference
``python/paddle/v2/fluid/param_attr.py``)."""

from .initializer import XavierInitializer, ConstantInitializer

__all__ = ["ParamAttr"]


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip

    @staticmethod
    def to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        if arg is False:
            return False
        raise TypeError("cannot convert %r to ParamAttr" % (arg,))

    def default_initializer(self, is_bias):
        if self.initializer is not None:
            return self.initializer
        return ConstantInitializer(0.0) if is_bias else XavierInitializer()
