"""Training-curve plotting (reference ``python/paddle/v2/plot/plot.py``
Ploter): append (step, value) per named curve; ``plot()`` renders via
matplotlib when available and otherwise writes/returns a CSV text dump
(this environment is headless — the data contract is the point)."""

__all__ = ["Ploter"]


class Ploter:
    def __init__(self, *titles):
        self.titles = list(titles)
        self.data = {t: ([], []) for t in titles}

    def append(self, title, step, value):
        if title not in self.data:
            raise KeyError("unknown curve %r (have %s)"
                           % (title, self.titles))
        xs, ys = self.data[title]
        xs.append(step)
        ys.append(float(value))

    def reset(self):
        for t in self.titles:
            self.data[t] = ([], [])

    def to_csv(self):
        lines = ["title,step,value"]
        for t in self.titles:
            xs, ys = self.data[t]
            lines += ["%s,%s,%s" % (t, x, y) for x, y in zip(xs, ys)]
        return "\n".join(lines)

    def plot(self, path=None):
        """Render to ``path``. PNG via matplotlib when importable, else
        CSV text. Returns the path (or the CSV string if path=None);
        render errors surface — only a missing matplotlib falls back."""
        if path is None:
            return self.to_csv()
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            with open(path, "w") as f:
                f.write(self.to_csv())
            return path
        fig, ax = plt.subplots()
        try:
            for t in self.titles:
                xs, ys = self.data[t]
                ax.plot(xs, ys, label=t)
            ax.legend()
            fig.savefig(path)
        finally:
            plt.close(fig)
        return path
