"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle (reference joegana/Paddle, surveyed in /root/repo/SURVEY.md),
re-designed for JAX/XLA:

* Program/Block/Op IR built by a fluid-style layers API,
* whole-block lowering to ONE jitted XLA computation per Executor.run
  (replacing the reference's per-op kernel interpreter),
* IR-level autodiff linked by jax.vjp at trace time,
* padded-sequence + lax.scan machinery replacing LoD,
* SPMD data/model parallelism over jax.sharding meshes replacing the
  pserver tier and NCCL ops.
"""

from .core.framework import (  # noqa: F401
    Program, Variable, Parameter, default_main_program,
    default_startup_program, program_guard)
from .core.scope import Scope, global_scope, scope_guard  # noqa: F401
from .core.executor import Executor  # noqa: F401
from .core.backward import append_backward  # noqa: F401
from .core import unique_name  # noqa: F401

from . import ops  # noqa: F401  (registers the op library)
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import nets  # noqa: F401
from . import config  # noqa: F401
from . import io  # noqa: F401
from . import reader  # noqa: F401
from . import evaluator  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import utils  # noqa: F401
from . import observability  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import resilience  # noqa: F401
from . import distributed  # noqa: F401  (paddle_elastic_* always-on)
from . import embeddings  # noqa: F401  (registers lookup_table_dist ops)
from .data_feeder import DataFeeder  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .place import CPUPlace, TPUPlace, CUDAPlace, is_compiled_with_tpu  # noqa: F401

__version__ = "0.1.0"
